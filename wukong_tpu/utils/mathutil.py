"""Hashing and sampling helpers (reference: utils/math.hpp).

``hash_mod`` is the load-balancing primitive used to place a vertex on a worker
(math.hpp:51, used by gstore.hpp:301 and base_loader.hpp:172-173). The rebuild
keeps the same function so partition assignment is deterministic and matches
between the host loader, the CPU engine, and the device all-to-all shuffle.
"""

from __future__ import annotations

import numpy as np


def hash_mod(v, n: int):
    """Partition id of vertex v among n workers. Works on scalars and arrays."""
    return v % n


def hash_u64(key: int) -> int:
    """Invertible 64-bit mix (math.hpp:58-80, Lemire-style). Used for bucket spread."""
    key = (~key + (key << 21)) & 0xFFFFFFFFFFFFFFFF
    key = key ^ (key >> 24)
    key = (key + (key << 3) + (key << 8)) & 0xFFFFFFFFFFFFFFFF
    key = key ^ (key >> 14)
    key = (key + (key << 2) + (key << 4)) & 0xFFFFFFFFFFFFFFFF
    key = key ^ (key >> 28)
    key = (key + (key << 31)) & 0xFFFFFFFFFFFFFFFF
    return key


def get_distribution(rng: np.random.Generator, weights) -> int:
    """Weighted choice index (math.hpp:36-49)."""
    w = np.asarray(weights, dtype=np.float64)
    return int(rng.choice(len(w), p=w / w.sum()))
