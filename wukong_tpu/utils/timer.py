"""Microsecond timer (reference: utils/timer.hpp:28-62)."""

from __future__ import annotations

import time


def get_usec() -> int:
    return time.perf_counter_ns() // 1000


class StopWatch:
    def __init__(self):
        self.start = get_usec()

    def elapsed_usec(self) -> int:
        return get_usec() - self.start

    def restart(self) -> int:
        now = get_usec()
        dt = now - self.start
        self.start = now
        return dt
