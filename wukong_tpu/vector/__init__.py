"""Hybrid graph+vector subsystem (ROADMAP item 5).

Dense per-vertex embeddings as a first-class store plane plus a batched
k-NN operator that composes with BGPs in both directions — the
GraphRAG-shaped workload class ("nearest neighbors of ?x that also
satisfy this graph pattern"), served through every existing plane
instead of bolted on the side:

- :mod:`wukong_tpu.vector.vstore` — the per-partition embedding store:
  ``[n_slots, dim]`` float32 blocks keyed by vertex id with tombstoned
  upserts, riding the WAL (``maybe_wal_append("vector", ...)`` before
  ack), the checkpoint bundles (persist.py carries the arrays,
  CRC'd and format-versioned), migration dual-write sinks, and the
  store-version protocol (every vector mutation bumps the owning
  partition's version, so plan/result/join-table caches invalidate
  exactly like they do for triples).
- :mod:`wukong_tpu.vector.knn` — the k-NN operator: one scoring seam
  (cosine / dot / L2) written against a swappable array module, run as
  plain NumPy on the host or as a jitted XLA batched-matmul + top-k
  scan on the device (``join/kernels.py`` posture), with slice-range
  splitting across the engine pool for wide scans (``join/dist.py``
  gather-barrier shape).

Everything is behind ``enable_vectors`` (default OFF — the actuator
posture: one knob check per knn-free query, serving path otherwise
byte-identical).
"""

from __future__ import annotations

#: every signal the vector plane emits, mapped to the registered metric
#: that backs it (the CACHE_INPUTS posture). The vector-coherence
#: analysis gate verifies each named metric is actually registered
#: somewhere in code and that this literal and the registrations never
#: drift apart.
VECTOR_METRICS = {
    "upserts": "wukong_vector_upserts_total",
    "tombstones": "wukong_vector_tombstones_total",
    "queries": "wukong_vector_queries_total",
    "routes": "wukong_vector_route_total",
    "route_demotions": "wukong_vector_route_demotions_total",
    "scan_latency": "wukong_vector_scan_us",
    "scan_slices": "wukong_vector_scan_slices_total",
}
