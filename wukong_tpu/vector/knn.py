"""The batched k-NN operator (the query half of the hybrid subsystem).

One scoring seam — :func:`scores` — written against a swappable array
module ``xp`` (the ``join/kernels.py`` posture): cosine / dot / L2 are a
batched matmul plus elementwise fixups, so the SAME function body runs
as plain NumPy on the host and traces into a jitted XLA batched-matmul +
``jax.lax.top_k`` scan on the device (candidates padded to a power-of-two
capacity class, dead/padding slots masked to ``-inf``). L2 ranks by
NEGATIVE squared distance so "higher score = nearer" holds across all
three metrics.

Composition with BGPs happens in the engine
(``CPUEngine._knn_seed`` / ``_knn_rank``): this module only ranks.
Ranking is deterministic — ties break by ``(score desc, vid asc)`` — so
the pattern-then-rank and rank-then-pattern replies are byte-identical
between routes whenever score gaps exceed float error (exact cross-route
score ties at the k boundary may differ: XLA and NumPy matmuls round
differently).

Wide scans split into slice ranges across the engine pool
(:func:`sliced_topk`) with the ``join/dist.py`` heavy-lane shape:
claim-once slices, a gather barrier, one inline per-slice retry, and
per-slice device->host fallback. Per-element scores are row-independent,
so the sliced merge is exactly the single-scan answer.
"""

from __future__ import annotations

import threading

import numpy as np

from wukong_tpu.analysis.lockdep import declare_leaf, make_lock
from wukong_tpu.utils.errors import ErrorCode, WukongError
from wukong_tpu.utils.timer import get_usec

#: the metric names behind the one kernel seam (knn_metric knob values)
KNN_METRICS = ("cosine", "dot", "l2")

#: device capacity-class floor (join/kernels.py PAD_FLOOR discipline)
PAD_FLOOR = 1024

# the slice claim lock guards one bool — innermost by construction,
# exactly join.slice
declare_leaf("vector.slice")

# chaos/bench seam: when set, the device scan path calls it before
# dispatch (raise to simulate a device failure; the measured-demotion
# drill and BENCH_GRAPHRAG's demotion check drive this)
_DEVICE_FAIL_HOOK = None


def _metrics():
    from wukong_tpu.obs.metrics import get_registry

    reg = get_registry()
    return (
        reg.histogram("wukong_vector_scan_us",
                      "k-NN scan latency (usec) by executed route",
                      labels=("route",)),
        reg.counter("wukong_vector_scan_slices_total",
                    "Wide k-NN scan slice-range dispatches"),
    )


_M_SCAN_US, _M_SLICES = _metrics()


def pad_pow2(n: int, floor: int = PAD_FLOOR) -> int:
    """Smallest power of two >= max(n, floor) — the device path's
    capacity class, so the jitted scan compiles a bounded set of shape
    variants instead of one per store size."""
    c = max(int(n), int(floor), 1)
    return 1 << (c - 1).bit_length()


def scores(base, queries, metric: str, xp=np):
    """``[B, N]`` similarity scores of ``queries [B, d]`` against
    ``base [N, d]`` — THE kernel seam (higher = nearer for every
    metric). Pure xp ops: traces under jit unchanged."""
    if metric == "dot":
        return queries @ base.T
    if metric == "cosine":
        qn = queries / xp.clip(
            xp.linalg.norm(queries, axis=1, keepdims=True), 1e-12, None)
        bn = base / xp.clip(
            xp.linalg.norm(base, axis=1, keepdims=True), 1e-12, None)
        return qn @ bn.T
    if metric == "l2":
        qq = xp.sum(queries * queries, axis=1, keepdims=True)  # [B, 1]
        bb = xp.sum(base * base, axis=1)  # [N]
        return -(qq - 2.0 * (queries @ base.T) + bb[None, :])
    raise WukongError(ErrorCode.UNSUPPORTED_SHAPE,
                      f"knn_metric must be one of {KNN_METRICS}, "
                      f"got {metric!r}")


def topk_host(vids, vecs, alive, anchor, k: int, metric: str):
    """NumPy brute-force top-k over live slots; the oracle every other
    route must match. Ties break ``(score desc, vid asc)``."""
    anchor = np.asarray(anchor, dtype=np.float32)
    if len(vids) == 0 or k <= 0:
        return (np.empty(0, dtype=np.int64), np.empty(0, dtype=np.float32))
    s = np.asarray(scores(vecs, anchor[None, :], metric, np)[0],
                   dtype=np.float32)
    s = np.where(alive, s, -np.inf)
    order = np.lexsort((vids, -s))
    order = order[np.isfinite(s[order])]
    sel = order[:int(k)]
    return vids[sel].copy(), s[sel].copy()


# jitted scan variants keyed on (metric, k); candidate shapes are
# handled by pad_pow2 bucketing, so the cache stays small
_SCAN_JIT_CACHE: dict = {}


def _jit_scan(metric: str, k: int):
    fn = _SCAN_JIT_CACHE.get((metric, k))
    if fn is None:
        import jax
        import jax.numpy as jnp

        def scan(base, mask, anchor):
            s = scores(base, anchor[None, :], metric, jnp)[0]
            s = jnp.where(mask, s, -jnp.inf)
            return jax.lax.top_k(s, k)

        fn = _SCAN_JIT_CACHE[(metric, k)] = jax.jit(scan)
    return fn


def topk_device(vids, vecs, alive, anchor, k: int, metric: str):
    """The jitted XLA scan: pad candidates to a capacity class, mask
    dead/padding slots, ``lax.top_k``, then re-order the k winners on
    the host by the canonical ``(score desc, vid asc)`` tie policy."""
    if _DEVICE_FAIL_HOOK is not None:
        _DEVICE_FAIL_HOOK()
    import jax.numpy as jnp

    anchor = np.asarray(anchor, dtype=np.float32)
    n = int(len(vids))
    if n == 0 or k <= 0:
        return (np.empty(0, dtype=np.int64), np.empty(0, dtype=np.float32))
    cap = pad_pow2(n)
    base = np.zeros((cap, vecs.shape[1]), dtype=np.float32)
    base[:n] = vecs
    mask = np.zeros(cap, dtype=bool)
    mask[:n] = alive
    kk = int(min(k, cap))
    t0 = get_usec()
    top_s, top_i = _jit_scan(metric, kk)(
        jnp.asarray(base), jnp.asarray(mask), jnp.asarray(anchor))
    top_s = np.asarray(top_s, dtype=np.float32)  # blocking D2H sync
    top_i = np.asarray(top_i)
    from wukong_tpu.obs.device import maybe_device_dispatch

    maybe_device_dispatch(
        "knn.scan", template=f"{metric}:k{kk}", live=n, capacity=cap,
        wall_us=get_usec() - t0,
        nbytes=int(base.nbytes) + int(mask.nbytes) + int(anchor.nbytes)
        + 8 * kk)
    ok = np.isfinite(top_s) & (top_i < n)
    sel_v = np.asarray(vids)[top_i[ok]]
    sel_s = top_s[ok]
    order = np.lexsort((sel_v, -sel_s))[:int(k)]
    return sel_v[order].copy(), sel_s[order].copy()


def scan_topk(vstore, anchor, k: int, metric: str, route: str = "host",
              shard: int | None = None):
    """One full-store scan through the route seam. Returns
    ``(top_vids, top_scores, demoted_reason | None)`` — a device-path
    failure degrades to the host kernels with the answer intact and the
    reason latched for the proxy's measured-demotion feedback
    (``JOIN_ROUTES`` posture). Charges the partition's heat accountant
    (one charge per scan, never per row)."""
    vids, vecs, alive, _ver = vstore.snapshot()
    t0 = get_usec()
    demoted = None
    used = "host"
    if route == "device":
        try:
            out = topk_device(vids, vecs, alive, anchor, k, metric)
            used = "device"
        except Exception as e:  # degrade, never fail the query
            demoted = (e.code.name if isinstance(e, WukongError)
                       else type(e).__name__)
            out = topk_host(vids, vecs, alive, anchor, k, metric)
    else:
        out = topk_host(vids, vecs, alive, anchor, k, metric)
    dur = get_usec() - t0
    _M_SCAN_US.labels(route=used).observe(dur)
    if shard is None:
        shard = getattr(vstore, "sid", 0)
    from wukong_tpu.obs.heat import get_heat

    get_heat().charge(int(shard), "vector", rows=int(len(vids)),
                      nbytes=int(vecs.nbytes), dur_us=int(dur))
    return out[0], out[1], demoted


def rank_candidates(vstore, cand_vids, anchor, k: int, metric: str,
                    route: str = "host"):
    """Top-k over an explicit candidate id set (pattern-then-rank: the
    BGP's binding set). Candidates missing from the store or tombstoned
    simply don't rank. Same return contract as :func:`scan_topk`."""
    cand = np.unique(np.asarray(cand_vids, dtype=np.int64))
    vids, vecs, alive, _ver = vstore.snapshot()
    if len(vids) == 0 or cand.size == 0 or k <= 0:
        return (np.empty(0, dtype=np.int64),
                np.empty(0, dtype=np.float32), None)
    slots = np.asarray([vstore.slot_of.get(int(v), -1) for v in cand],
                       dtype=np.int64)
    hit = slots >= 0
    cand, slots = cand[hit], slots[hit]
    sub_vecs = vecs[slots] if len(slots) else vecs[:0]
    sub_alive = alive[slots] if len(slots) else alive[:0]
    t0 = get_usec()
    demoted = None
    used = "host"
    if route == "device":
        try:
            out = topk_device(cand, sub_vecs, sub_alive, anchor, k, metric)
            used = "device"
        except Exception as e:
            demoted = (e.code.name if isinstance(e, WukongError)
                       else type(e).__name__)
            out = topk_host(cand, sub_vecs, sub_alive, anchor, k, metric)
    else:
        out = topk_host(cand, sub_vecs, sub_alive, anchor, k, metric)
    _M_SCAN_US.labels(route=used).observe(get_usec() - t0)
    return out[0], out[1], demoted


def resolve_anchor(vstore, clause) -> np.ndarray:
    """The clause's anchor as a ``[dim]`` float32 vector: a literal
    vector must match the store's fixed ``vector_dim``; a vertex anchor
    must have a live embedding."""
    if clause.anchor_vec is not None:
        vec = np.asarray(clause.anchor_vec, dtype=np.float32).ravel()
        if vstore is not None and len(vec) != vstore.dim:
            raise WukongError(
                ErrorCode.UNSUPPORTED_SHAPE,
                f"knn literal vector has dim {len(vec)}, store has "
                f"{vstore.dim}")
        return vec
    if vstore is None:
        raise WukongError(ErrorCode.UNSUPPORTED_SHAPE,
                          "knn() anchor needs an attached vector store")
    vec = vstore.get(int(clause.anchor_vid))
    if vec is None:
        raise WukongError(
            ErrorCode.VERTEX_INVALID,
            f"knn() anchor vertex {clause.anchor_vid} has no live "
            "embedding")
    return np.asarray(vec, dtype=np.float32)


def classify_knn_mode(q) -> str:
    """The composition direction (EXPLAIN shows it):

    - ``scan`` — no graph patterns: a pure ranked scan;
    - ``rank_then_pattern`` — the chain STARTS at the knn variable:
      the scan seeds the chain (a seeded walk);
    - ``pattern_then_rank`` — anything else: the BGP runs first and
      the scan ranks its binding set.

    The parser stamps the direction from the TEXTUAL pattern order
    (``KNNClause.mode``) — preferred here, because a planner reorder
    after parse must not flip the semantics. The shape-derived fallback
    covers hand-built queries."""
    mode = getattr(q.knn, "mode", "")
    if mode:
        return mode
    pg = q.pattern_group
    if not pg.patterns:
        return "scan"
    if pg.patterns[0].subject == q.knn.var:
        return "rank_then_pattern"
    return "pattern_then_rank"


# ---------------------------------------------------------------------------
# wide-scan slice split (join/dist.py heavy-lane shape)
# ---------------------------------------------------------------------------


class _KnnSlice:
    """One slot-range slice of a wide scan: a fire-and-forget heavy-lane
    pool item claimable exactly once; engine-thread death reaches
    :meth:`fail_all` via the scheduler's death handler, so the gather
    barrier always wakes."""

    lane = "heavy"

    __slots__ = ("vids", "vecs", "alive", "anchor", "k", "metric",
                 "route", "result", "demoted", "event", "error",
                 "_claim_lock", "_claimed")

    def __init__(self, vids, vecs, alive, anchor, k, metric, route):
        self.vids = vids
        self.vecs = vecs
        self.alive = alive
        self.anchor = anchor
        self.k = k
        self.metric = metric
        self.route = route
        self.result = None
        self.demoted: str | None = None
        self.event = threading.Event()
        self.error: BaseException | None = None
        self._claim_lock = make_lock("vector.slice")
        self._claimed = False  # guarded by: _claim_lock

    def claim(self) -> bool:
        with self._claim_lock:
            if self._claimed:
                return False
            self._claimed = True
            return True

    def run(self, engine=None) -> None:
        if not self.claim():
            return
        self._execute()

    def _execute(self) -> None:
        ok = False
        try:
            if self.route == "device":
                try:
                    self.result = topk_device(self.vids, self.vecs,
                                              self.alive, self.anchor,
                                              self.k, self.metric)
                except Exception as e:
                    # per-slice fallback: this slice degrades to host,
                    # the others keep their route
                    self.demoted = (e.code.name if isinstance(e, WukongError)
                                    else type(e).__name__)
                    self.result = topk_host(self.vids, self.vecs,
                                            self.alive, self.anchor,
                                            self.k, self.metric)
            else:
                self.result = topk_host(self.vids, self.vecs, self.alive,
                                        self.anchor, self.k, self.metric)
            ok = True
        except BaseException as e:
            self.error = e
        finally:
            if not ok and self.error is None:
                self.error = RuntimeError("knn slice aborted")
            self.event.set()

    def retry_inline(self) -> None:
        self.error = None
        self._execute()

    def fail_all(self, exc: BaseException) -> None:
        """Scheduler death-handler / dead-pool contract."""
        if not self.event.is_set():
            self.error = exc
            self.event.set()


def sliced_topk(pool, vstore, anchor, k: int, metric: str,
                route: str, parts: int):
    """Wide-scan fan-out: split the slot range into ``parts`` slices
    across the engine pool's heavy lane, each computing its local
    top-k; the gather thread works slice 0 itself, claims stragglers
    inline, retries a failed slice once, and merges by the canonical
    ``(score desc, vid asc)`` order — exactly the single-scan answer,
    since per-element scores are row-independent. Returns
    ``(top_vids, top_scores, demoted_reason | None)``."""
    from wukong_tpu.runtime.batcher import (
        HEAVY_GATHER_WAIT_S,
        SLICE_CLAIM_GRACE_S,
    )

    vids, vecs, alive, _ver = vstore.snapshot()
    n = int(len(vids))
    parts = max(min(int(parts), max(n, 1)), 1)
    if parts <= 1 or pool is None:
        return scan_topk(vstore, anchor, k, metric, route=route)
    t0 = get_usec()
    bounds = np.linspace(0, n, parts + 1).astype(np.int64)
    slices = [
        _KnnSlice(vids[bounds[i]:bounds[i + 1]],
                  vecs[bounds[i]:bounds[i + 1]],
                  alive[bounds[i]:bounds[i + 1]],
                  anchor, k, metric, route)
        for i in range(parts)]
    _M_SLICES.inc(len(slices))
    for s in slices[1:]:
        try:
            pool.submit(s, lane="heavy")
        except Exception:
            pass  # claimed and run inline below
    slices[0].run(None)  # the gather thread works its own share first
    for s in slices[1:]:
        if not s.event.wait(SLICE_CLAIM_GRACE_S):
            if s.claim():  # not started yet: run the straggler inline
                s._execute()
            elif not s.event.wait(HEAVY_GATHER_WAIT_S):
                raise WukongError(
                    ErrorCode.UNKNOWN_PATTERN,
                    "knn gather barrier timed out on a claimed slice")
    demoted = None
    for s in slices:
        if s.error is not None:
            # one inline retry on the gather thread; a second failure
            # surfaces to the caller (the engine degrades the scan to
            # its own single-threaded host path)
            s.retry_inline()
            if s.error is not None:
                raise s.error
        if s.demoted is not None:
            demoted = s.demoted
    all_v = np.concatenate([s.result[0] for s in slices])
    all_s = np.concatenate([s.result[1] for s in slices])
    order = np.lexsort((all_v, -all_s))[:int(k)]
    dur = get_usec() - t0
    _M_SCAN_US.labels(
        route="device" if route == "device" and demoted is None
        else "host").observe(dur)
    from wukong_tpu.obs.heat import get_heat

    get_heat().charge(int(getattr(vstore, "sid", 0)), "vector",
                      rows=n, nbytes=int(vecs.nbytes), dur_us=int(dur))
    return all_v[order].copy(), all_s[order].copy(), demoted
