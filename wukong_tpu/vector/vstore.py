"""Per-partition dense embedding store (the vector half of the hybrid
graph+vector subsystem).

One :class:`VectorStore` hangs off each :class:`GStore` partition
(``g.vstore``, attached by :func:`attach_vstore`) and holds a
``[n_slots, dim]`` float32 block keyed by vertex id, with tombstoned
upserts. It deliberately mirrors the triple store's disciplines instead
of inventing new ones:

- **Durability**: :func:`upsert_batch_into` is the primary mutation
  path — ``maybe_wal_append("vector", ...)`` fires BEFORE any store
  mutates (``dynamic.insert_batch_into`` parity), so an acknowledged
  batch is always replayable and a WAL failure leaves every store
  untouched. Recovery and migration catch-up re-apply the records via
  :func:`apply_vector_record`.
- **Versioning**: every mutation bumps BOTH the vstore's own version
  and the owning partition's ``g.version`` (:func:`bump_store_version`)
  — the plan cache, result cache, join-table cache, and the k-NN route
  memos all key on the store version, so vector mutations invalidate
  them exactly like triple inserts do.
- **Snapshot reads**: slot arrays are copy-on-write and published
  write-protected (``setflags(write=False)``, the result-cache
  posture): a scan grabs coherent immutable references under the slot
  lock and computes outside it; a racing upsert publishes NEW arrays,
  never mutates the ones a reader holds.
- **Partitioning**: ownership is ``hash_mod(vid, num_workers) == sid``,
  the triple store's subject rule, so a batch fans out across a shard
  pool the same way an insert batch does.
"""

from __future__ import annotations

import zlib

import numpy as np

from wukong_tpu.analysis.lockdep import declare_leaf, make_lock
from wukong_tpu.utils.errors import ErrorCode, WukongError
from wukong_tpu.utils.mathutil import hash_mod

# the slot lock guards array-reference swaps and dict replacement only —
# innermost by construction, like heat.shard (scans copy references out
# and compute outside it)
declare_leaf("vector.slots")


def _metrics():
    from wukong_tpu.obs.metrics import get_registry

    reg = get_registry()
    return (
        reg.counter("wukong_vector_upserts_total",
                    "Embedding vectors upserted (post-ownership-filter)"),
        reg.counter("wukong_vector_tombstones_total",
                    "Embedding slots tombstoned"),
    )


class VectorStore:
    """One partition's embedding block: vertex id -> ``dim`` float32s."""

    def __init__(self, sid: int, num_workers: int, dim: int):
        if int(dim) <= 0:
            raise WukongError(ErrorCode.UNSUPPORTED_SHAPE,
                              f"vector_dim must be positive, got {dim}")
        self.sid = int(sid)
        self.num_workers = int(num_workers)
        self.dim = int(dim)
        self._lock = make_lock("vector.slots")
        m_up, m_tomb = _metrics()
        self._m_upserts = m_up
        self._m_tombstones = m_tomb
        vids = np.empty(0, dtype=np.int64)
        vecs = np.empty((0, self.dim), dtype=np.float32)
        alive = np.empty(0, dtype=bool)
        for a in (vids, vecs, alive):
            a.setflags(write=False)
        # reference swaps only under the lock; the arrays themselves are
        # immutable (write-protected) and slot_of is replaced wholesale
        self.vids = vids  # guarded by: _lock
        self.vecs = vecs  # guarded by: _lock
        self.alive = alive  # guarded by: _lock
        self.slot_of: dict[int, int] = {}  # guarded by: _lock
        self.version = 0  # guarded by: _lock

    # ------------------------------------------------------------------
    # the single mutation primitive
    # ------------------------------------------------------------------
    def _apply_slots(self, vids: np.ndarray, vecs: np.ndarray | None,
                     tombstone: bool) -> int:
        """THE slot writer (vector-coherence gate contract: no other
        function touches the slot state, and this one always bumps the
        version). Copy-on-write: builds fresh arrays, publishes them
        write-protected under the lock. New vertex ids append in sorted
        order so the slot layout is canonical — a WAL-replayed store is
        byte-identical to the uninterrupted one. Returns slots written."""
        vids = np.asarray(vids, dtype=np.int64).ravel()
        if vids.size == 0:
            return 0
        if not tombstone:
            vecs = np.asarray(vecs, dtype=np.float32)
            if vecs.ndim != 2 or vecs.shape != (len(vids), self.dim):
                raise WukongError(
                    ErrorCode.UNSUPPORTED_SHAPE,
                    f"vector batch shape {getattr(vecs, 'shape', None)} != "
                    f"({len(vids)}, {self.dim}) (vector_dim is fixed)")
            # in-batch dedup: the LAST occurrence of a vid wins (upsert
            # semantics); np.unique keeps the first, so reverse first
            rev = vids[::-1]
            _, first = np.unique(rev, return_index=True)
            keep = np.sort(len(vids) - 1 - first)
            vids, vecs = vids[keep], vecs[keep]
        else:
            vids = np.unique(vids)
        with self._lock:
            cur_vids = np.array(self.vids)  # writable working copies
            cur_vecs = np.array(self.vecs)
            cur_alive = np.array(self.alive)
            slot_of = dict(self.slot_of)
            known = np.asarray([slot_of.get(int(v), -1) for v in vids],
                               dtype=np.int64)
            hit = known >= 0
            if tombstone:
                written = int(hit.sum())
                cur_alive[known[hit]] = False
            else:
                cur_vecs[known[hit]] = vecs[hit]
                cur_alive[known[hit]] = True
                fresh_v = vids[~hit]
                if fresh_v.size:
                    order = np.argsort(fresh_v, kind="stable")
                    fresh_v = fresh_v[order]
                    fresh_x = vecs[~hit][order]
                    base = len(cur_vids)
                    for i, v in enumerate(fresh_v):
                        slot_of[int(v)] = base + i
                    cur_vids = np.concatenate([cur_vids, fresh_v])
                    cur_vecs = np.concatenate([cur_vecs, fresh_x], axis=0)
                    cur_alive = np.concatenate(
                        [cur_alive, np.ones(len(fresh_v), dtype=bool)])
                written = int(len(vids))
            for a in (cur_vids, cur_vecs, cur_alive):
                a.setflags(write=False)
            self.vids = cur_vids
            self.vecs = cur_vecs
            self.alive = cur_alive
            self.slot_of = slot_of
            self.version += 1
        return written

    # ------------------------------------------------------------------
    # mutation API (ownership-filtered, metric-charged)
    # ------------------------------------------------------------------
    def owned_mask(self, vids: np.ndarray) -> np.ndarray:
        vids = np.asarray(vids, dtype=np.int64)
        return hash_mod(vids, self.num_workers) == self.sid

    def upsert(self, vids, vecs) -> int:
        """Ownership-filtered batch upsert; returns vectors written."""
        vids = np.asarray(vids, dtype=np.int64).ravel()
        vecs = np.asarray(vecs, dtype=np.float32)
        mine = self.owned_mask(vids)
        n = self._apply_slots(vids[mine], vecs[mine], tombstone=False)
        if n:
            self._m_upserts.inc(n)
        return n

    def tombstone(self, vids) -> int:
        """Ownership-filtered batch delete (slots stay, flagged dead —
        a later upsert of the same vid revives the slot in place)."""
        vids = np.asarray(vids, dtype=np.int64).ravel()
        n = self._apply_slots(vids[self.owned_mask(vids)], None,
                              tombstone=True)
        if n:
            self._m_tombstones.inc(n)
        return n

    # ------------------------------------------------------------------
    # read side
    # ------------------------------------------------------------------
    def snapshot(self):
        """Coherent immutable (vids, vecs, alive, version) references —
        grab under the lock, scan outside it."""
        with self._lock:
            return self.vids, self.vecs, self.alive, self.version

    def get(self, vid: int) -> np.ndarray | None:
        with self._lock:
            slot = self.slot_of.get(int(vid))
            if slot is None or not bool(self.alive[slot]):
                return None
            return self.vecs[slot]

    def live_count(self) -> int:
        with self._lock:
            return int(self.alive.sum())

    def n_slots(self) -> int:
        with self._lock:
            return int(len(self.vids))

    def memory_bytes(self) -> int:
        with self._lock:
            return int(self.vecs.nbytes + self.vids.nbytes
                       + self.alive.nbytes)

    def digest(self) -> int:
        """Order-sensitive content digest (recovery parity drills)."""
        vids, vecs, alive, _v = self.snapshot()
        crc = zlib.crc32(np.ascontiguousarray(vids).tobytes())
        crc = zlib.crc32(np.ascontiguousarray(vecs).tobytes(), crc)
        crc = zlib.crc32(np.ascontiguousarray(alive).tobytes(), crc)
        return crc

    # ------------------------------------------------------------------
    # persist / clone plumbing (store/persist.py carries these arrays
    # inside the gstore bundle, CRC'd like every other array)
    # ------------------------------------------------------------------
    def export_arrays(self) -> dict:
        vids, vecs, alive, _v = self.snapshot()
        return {"vstore_vids": vids, "vstore_vecs": vecs,
                "vstore_alive": alive.astype(np.uint8)}

    @classmethod
    def from_arrays(cls, sid: int, num_workers: int, vids: np.ndarray,
                    vecs: np.ndarray, alive: np.ndarray,
                    version: int = 0) -> "VectorStore":
        vs = cls(sid, num_workers, int(vecs.shape[1]) if vecs.ndim == 2
                 and vecs.shape[1] else 1)
        vids = np.asarray(vids, dtype=np.int64)
        vecs = np.asarray(vecs, dtype=np.float32)
        alive = np.asarray(alive).astype(bool)
        for a in (vids, vecs, alive):
            a.setflags(write=False)
        with vs._lock:
            vs.vids = vids
            vs.vecs = vecs
            vs.alive = alive
            vs.slot_of = {int(v): i for i, v in enumerate(vids)}
            vs.version = int(version)
        return vs

    def clone(self) -> "VectorStore":
        """Snapshot copy for shard replication/migration (arrays are
        immutable — sharing references is safe, the CSR-base posture)."""
        vids, vecs, alive, version = self.snapshot()
        return VectorStore.from_arrays(self.sid, self.num_workers, vids,
                                       vecs, alive, version=version)


# ---------------------------------------------------------------------------
# store attachment + the durable commit path
# ---------------------------------------------------------------------------


def attach_vstore(g, dim: int | None = None) -> VectorStore:
    """Create (or return) ``g.vstore`` with the partition's identity."""
    vs = getattr(g, "vstore", None)
    if vs is None:
        from wukong_tpu.config import Global

        dim = int(Global.vector_dim if dim is None else dim)
        vs = VectorStore(getattr(g, "sid", 0),
                         getattr(g, "num_workers", 1), dim)
        g.vstore = vs
    return vs


def bump_store_version(g) -> int:
    """The store-version protocol: vector mutations invalidate every
    version-keyed cache exactly like triple mutations do."""
    g.version = getattr(g, "version", 0) + 1
    return g.version


def _apply_to_store(g, vids, vecs, tombstone: bool, dim: int) -> int:
    """Apply one vector batch to a partition: attach-on-demand (replay
    onto a fresh world must not fail), write, bump the store version."""
    if tombstone:
        if getattr(g, "vstore", None) is None:
            return 0  # nothing attached, nothing to kill
        vs = g.vstore
    else:
        vs = attach_vstore(g, dim)
        if vs.dim != dim:
            raise WukongError(
                ErrorCode.UNSUPPORTED_SHAPE,
                f"vector batch dim {dim} != attached vector_dim {vs.dim}")
    n = vs.tombstone(vids) if tombstone else vs.upsert(vids, vecs)
    bump_store_version(g)
    return n


def upsert_batch_into(stores: list, vids, vecs=None, dedup: bool = True,
                      tombstone: bool = False) -> int:
    """One durable vector batch into every partition — the
    ``insert_batch_into`` twin. The ``vector.upsert`` fault site fires
    BEFORE the WAL append, so an injected failure leaves the WAL and
    every vstore untouched (the batch was never acknowledged); the WAL
    append fires before any store mutates, so an acknowledged batch is
    always replayable. In-flight migrations see the batch through their
    dual-write sinks, and the serving plane's invalidation edge lands
    INSIDE the mutation lock (the insert-batch contract)."""
    from wukong_tpu.obs.reuse import maybe_note_invalidation
    from wukong_tpu.runtime import faults
    from wukong_tpu.serve import notify_mutation
    from wukong_tpu.store.dynamic import migration_sinks
    from wukong_tpu.store.wal import maybe_wal_append, mutation_lock

    vids = np.asarray(vids, dtype=np.int64).ravel()
    if len(vids) and (int(vids.min()) < 0
                      or int(vids.max()) >= 2**31 - 1):
        raise WukongError(ErrorCode.UNKNOWN_PATTERN,
                          "vector vertex ids must be in [0, 2^31-1)")
    if tombstone:
        dim = (stores[0].vstore.dim if stores
               and getattr(stores[0], "vstore", None) is not None else 0)
        vecs_arr = None
    else:
        vecs_arr = np.asarray(vecs, dtype=np.float32)
        if vecs_arr.ndim != 2 or vecs_arr.shape[0] != len(vids):
            raise WukongError(
                ErrorCode.UNSUPPORTED_SHAPE,
                f"expected [{len(vids)}, dim] float32 vectors, got "
                f"{vecs_arr.shape}")
        dim = int(vecs_arr.shape[1])
    faults.site("vector.upsert")
    with mutation_lock():
        maybe_wal_append("vector", vids, dedup,
                         vecs=vecs_arr, tombstone=bool(tombstone),
                         dim=int(dim))
        total = 0
        for g in stores:
            total += _apply_to_store(g, vids, vecs_arr, tombstone, dim)
        # dual-write: an in-flight migration's recipient mirrors the
        # batch (excluded from the total — the sink is a transient
        # mirror of a store already counted)
        for g in migration_sinks():
            _apply_to_store(g, vids, vecs_arr, tombstone, dim)
        if stores:
            notify_mutation("vector",
                            version=getattr(stores[0], "version", 0))
    if stores:
        maybe_note_invalidation(
            "vector", version=getattr(stores[0], "version", 0),
            n_vecs=int(len(vids)), tombstone=bool(tombstone))
    return total


def apply_vector_record(g, payload: dict) -> int:
    """Re-apply one WAL ``vector`` record to a partition (recovery
    replay, migration catch-up, shard rebuild). No WAL hook, no serving
    notification — the callers own both."""
    vids = np.asarray(payload["triples"], dtype=np.int64).ravel()
    tomb = bool(payload.get("tombstone"))
    vecs = payload.get("vecs")
    dim = int(payload.get("dim") or
              (vecs.shape[1] if vecs is not None else 0))
    return _apply_to_store(g, vids, vecs, tomb, dim)
